"""Wire-verb parity audit: reference RedisCommands.java vs our registry.

Living artifact (VERDICT r4 next-step #9): run
    python tools/verb_audit.py [--ref /root/reference]
and paste the emitted table into PARITY.md.  The script extracts every verb
name the reference's command table defines, diffs it against the verbs the
server registry actually registers, and classifies the remainder against
the N/A table below so future rounds stop re-litigating the tail.
"""
from __future__ import annotations

import argparse
import re
import sys
from pathlib import Path

# verbs the reference defines that this framework deliberately does not
# serve, with the reason — reviewed per round, not auto-generated
NA = {
    # JVM-codec / connection-machinery internals
    "AUTH2": "HELLO AUTH form covers it (net/resp.py HELLO)",
    "SENTINEL": "sentinel topology out of scope: replicated/cluster coordinators cover failover (SURVEY §7.4)",
    "FAILOVER": "HA failover is coordinator-driven (server/monitor.py), not verb-driven",
    "MIGRATE": "record migration rides IMPORTRECORDS/TRANSFER frames (server/migration.py)",
    "DUMP": "object lifecycle rides core/checkpoint.py record codec (OBJCALL dump/restore)",
    "RESTORE": "see DUMP",
    "DEBUG": "server introspection rides INFO/METRICS",
    "RESET_": "RESET is served (tx family)",
    "SWAPDB": "single-keyspace engine; SELECT is accepted for db 0 only",
    "MOVE": "single-keyspace engine",
    "WAITAOF": "no AOF: durability is checkpoint/replication (SAVE/RESTORESTATE, REPLPUSH)",
    "TOUCH": "LRU bookkeeping is engine-internal; EXISTS covers the client use",
    "RANDOMKEY": "no reference caller in redisson; trivially expressible via KEYS",
    "READONLY": "replica reads are routed client-side (client/cluster.py)",
    "READWRITE": "see READONLY",
    "CLUSTER_NODES": "CLUSTER subcommands are served via the CLUSTER verb",
    "LPOS": "RList.indexOf rides OBJCALL indexOf (no wire caller in reference either)",
    "OBJECT": "encoding introspection is meaningless for device-resident records",
    "LOLWUT": "easter egg",
}

def reference_verbs(ref_root: Path) -> set:
    src = (ref_root / "redisson/src/main/java/org/redisson/client/protocol/RedisCommands.java").read_text()
    # new RedisCommand<...>("VERB"[, "SUB"...]) and RedisStrictCommand("VERB")
    names = set()
    for m in re.finditer(r'new\s+Redis\w*Command[^(]*\(\s*"([A-Z][A-Z0-9._ -]*)"(?:\s*,\s*"([A-Za-z0-9 _-]+)")?', src):
        names.add(m.group(1))
    return names

def our_verbs() -> set:
    import jax
    jax.config.update("jax_platforms", "cpu")
    from redisson_tpu.server.registry import REGISTRY
    return {k.decode() for k in REGISTRY._handlers}

def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    args = ap.parse_args()
    ref = reference_verbs(Path(args.ref))
    ours = our_verbs()
    missing = sorted(v for v in ref if v not in ours)
    extra = sorted(v for v in ours if v not in ref)
    unexplained = [v for v in missing if v.replace(" ", "_") not in NA and v not in NA]
    print(f"reference verbs: {len(ref)}; registered here: {len(ours)}")
    print(f"covered: {len(ref) - len(missing)}; missing: {len(missing)} "
          f"({len(missing) - len(unexplained)} documented N/A, "
          f"{len(unexplained)} UNEXPLAINED)")
    print("\n## N/A (deliberate, with reasons)\n")
    for v in missing:
        key = v.replace(" ", "_") if v.replace(" ", "_") in NA else v
        if key in NA:
            print(f"| {v} | {NA[key]} |")
    if unexplained:
        print("\n## UNEXPLAINED (implement or document)\n")
        for v in unexplained:
            print(f"  {v}")
    print(f"\n## Extra verbs (ours beyond the reference): {len(extra)}")
    print("  " + " ".join(extra))
    return 1 if unexplained else 0

if __name__ == "__main__":
    sys.exit(main())
