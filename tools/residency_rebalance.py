#!/usr/bin/env python
"""Standalone fleet-wide HBM pressure rebalancer (ISSUE 20).

Runs the cluster/residency_control.py control loop against ANY fleet
addressed by host:port — sidecar-style, like tools/qos_rebalance.py: scrape
every node's ``CLUSTER RESIDENCY`` per-device tier ledgers, ask pressured
devices to demote first (``CLUSTER RESIDENCY SWEEP``), and shed devices
whose HOT working set outgrows the budget through the journaled fenced
device rebalance (``CLUSTER RESIDENCY SHED``).

    python tools/residency_rebalance.py 127.0.0.1:7000 127.0.0.1:7001 \
        --interval 1.0 --high-water 0.9 --shed-count 64

Runs until interrupted; ``--sweeps N`` exits after N sweeps (smoke/CI use).
"""
from __future__ import annotations

import argparse
import sys
import time
from contextlib import closing


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fleet-wide HBM pressure rebalancer"
    )
    ap.add_argument("nodes", nargs="+", metavar="HOST:PORT",
                    help="nodes whose device ledgers to defend")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="seconds between control-loop sweeps")
    ap.add_argument("--high-water", type=float, default=0.9,
                    help="pressure threshold as a fraction of the budget")
    ap.add_argument("--shed-after", type=int, default=2,
                    help="consecutive pressured sweeps before a shed")
    ap.add_argument("--shed-count", type=int, default=8,
                    help="slots moved per shed step")
    ap.add_argument("--journal-dir", default=None,
                    help="journal directory passed to SHED (resumable)")
    ap.add_argument("--budget", type=int, default=None,
                    help="override per-device byte budget (default: trust "
                         "each node's device-budget-bytes)")
    ap.add_argument("--password", default=None)
    ap.add_argument("--ca-cert", default=None, metavar="PEM",
                    help="fleet CA certificate: speak TLS to the nodes")
    ap.add_argument("--sweeps", type=int, default=0,
                    help="exit after this many sweeps (0 = run forever)")
    args = ap.parse_args(argv)

    from redisson_tpu.cluster.residency_control import ResidencyRebalancer
    from redisson_tpu.net.client import Connection

    ssl_context = None
    if args.ca_cert:
        from redisson_tpu.net.client import client_ssl_context

        ssl_context = client_ssl_context(
            ca_file=args.ca_cert, verify_hostname=False,
        )

    def factory(addr: str):
        host, _, port = addr.rpartition(":")

        def open_conn():
            return closing(Connection(host, int(port), timeout=10.0,
                                      password=args.password,
                                      ssl_context=ssl_context))

        return open_conn

    rb = ResidencyRebalancer(
        {a: factory(a) for a in args.nodes},
        interval=args.interval, high_water=args.high_water,
        shed_after=args.shed_after, shed_count=args.shed_count,
        journal_dir=args.journal_dir, budget_bytes=args.budget,
    )
    n = 0
    try:
        while True:
            actions = rb.step()
            n += 1
            for node, action, dev in actions:
                print(f"[sweep {n}] {node} dev{dev}: {action}", flush=True)
            if args.sweeps and n >= args.sweeps:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
