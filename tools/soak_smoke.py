#!/usr/bin/env python
"""10-second soak for local sanity, two profiles:

  * ``standard`` (default) — one full chaos cycle (workload under injected
    transport faults -> master kill -> automatic failover -> recovery ->
    mesh reshard 4 -> 8 -> 4) with the same zero-acked-write-loss and
    flat-census assertions the slow endurance tier enforces.
  * ``migration`` — the crash-safe control-plane profile: a mixed write
    stream over a slot range while the JOURNALED migration coordinator is
    killed at every phase boundary (PLANNED, WINDOW_OPEN, mid-DRAINING,
    VIEW_COMMITTED) and resumed via ``resume_migrations``, plus
    torn-write/ENOSPC checkpoint chaos.  Asserts zero acked-write loss,
    no slot left non-STABLE, bit-identical record contents, checkpoint
    generation fallback, flat census.  One kill-resume cycle runs in well
    under 60s.
  * ``fleet`` — the fleet-lifecycle profile (ISSUE 13): a replica-covered
    multi-process cluster under client-side transport faults takes a full
    rolling restart (graceful drains, zero acked loss), a coordinator+
    TARGET double-kill at a journal phase (recovered by the target's
    import-journal replay), a replica promotion that carries an in-flight
    import window across a failover, and a live-coordinator target
    SIGKILL whose journal must stay resumable.  Asserts zero
    acked-durable-write loss, exactly-one-owner, all slots STABLE with
    import journals terminal, bloom adds intact, flat client census.
  * ``fleet-host`` — the failure-DOMAIN profile (ISSUE 16): the fleet
    spans two host labels via the real ssh-driver command pipeline
    (loopback transport), placement is host-anti-affine and the bus is
    TLS-armed; mid-drain the import target's WHOLE host is SIGKILLed and
    partitioned at once, then recovery restarts the surviving master's
    replica, promotes the target's off-host replica, resumes the import
    readdressed to it, and rejoins the old target as a replica.  Asserts
    zero acked-durable-write loss, exactly-one-owner, all slots STABLE,
    bloom adds intact, flat client census.
  * ``cluster-proc`` — the PROCESS-LEVEL profile (ISSUE 6): real
    ``tpu-server`` OS processes under a ClusterSupervisor serve a mixed
    write stream over real TCP while the coordinator dies at a journal
    phase AND the source master takes an actual SIGKILL; the supervisor
    restarts it (``--restore`` + journal re-arm) and ``resume_migrations``
    settles the journal across the process boundary.  Asserts zero
    acked-durable-write loss, exactly-one-owner residency, all slots
    STABLE, acked bloom adds intact.  One two-phase cycle runs in well
    under 60s.
  * ``device-shard`` — the device-sharded serving profile (ISSUE 8): mixed
    bucket/bloom traffic plus tracked zipf readers against ONE server
    owning 8 (forced host) devices while the slot table rebalances across
    devices 8 -> 4 -> 8 through the journaled fenced handoff path, under
    injected transport faults.  Asserts zero acked-write loss, zero stale
    tracked reads (a device move must be invisible to the tracking plane),
    near-cache convergence after quiesce, per-device lane census flat, and
    zero host-side cross-device gathers (IOStats.host_colocations == 0).
  * ``residency`` — the tiered-HBM residency profile (ISSUE 20): zipf
    tenant bloom banks whose combined footprint is 4x the armed per-device
    byte budget keep serving membership probes (demote-to-host + fault-in
    on first touch) plus tracked bucket readers, under transport faults,
    while the slot table rebalances 8 -> 4 -> 8 AND the
    ResidencyRebalancer control loop sheds pressured devices through the
    journaled fenced rebalance.  Asserts zero acked-write loss, zero
    stale tracked reads, post-storm recall >= 0.99 for banks force-spilled
    COLD and faulted back, per-tier census flat at quiesce, and a DELed
    COLD bank draining its census rows and spill file to absence.
  * ``device-fault`` — the device fault-domain profile (ISSUE 19): mixed
    bucket/bloom/KNN traffic plus tracked readers against one
    device-sharded server while device lanes are killed (kernel-launch
    failures trip quarantine), hung (an armed lane watchdog bounds the
    stalled readback and fails the frame retryable) and OOMed (a bank
    growth degrades to ONE clean ``-OOM`` with rows kept pending), then
    the quarantined lane is evacuated MID-TRAFFIC through the journaled
    fenced rebalance, probed back healthy (``CLUSTER DEVPROBE``) and
    respread.  Asserts zero acked-write loss, zero stale tracked reads,
    bit-identical bank rows post-evacuation, flat lane census, and
    host_colocations unmoved.  One cycle runs in well under 60s.
  * ``qos`` — the tail-latency/QoS profile (ISSUE 10): an abusive bulk
    tenant floods one master with big blob pipelines while interactive
    tenants keep reading/writing small keys, under transport faults, while
    interactive-key slots migrate m0 -> m1 -> m0.  Asserts zero
    acked-write loss, bounded interactive p99 (no starvation), sheds
    landing ONLY on the over-budget tenant, and flat QoS ledgers at
    quiesce.
  * ``vector`` — the vector-search profile (ISSUE 11): KNN readers with
    tracked near-cached query results + concurrent HSET ingest while the
    index's slots (embedding-bank record included) rebalance 8 -> 4 -> 8
    across devices under transport faults.  Asserts zero stale tracked
    KNN results, zero acked-ingest loss, post-storm recall@k >= 0.99 vs a
    float64 brute-force oracle, and a flat embedding-bank census after
    FT.DROPINDEX.  ``--shards n`` (> 1) runs the MESH-SHARDED leg
    (ISSUE 15): the bank splits across n shard records on distinct
    devices, reads exercise the fan-out + on-device top-k merge while the
    constellation rebalances, and the run additionally asserts
    host_colocations unmoved (never a host gather) with
    sharded_knn_merges > 0 and per-device census rows flat.
  * ``read-scale`` — the replica read-scaling profile (ISSUE 17): tracked
    zipf readers route every keyed read to REPLICAS (read_mode=replica +
    the bounded-staleness probe) while key slots migrate m0 -> m1 -> m0,
    a replica is killed mid-traffic (reads must drain to the master), and
    the write-owning master is killed and promoted.  Asserts ZERO stale
    tracked reads (replica-side tracking tables must invalidate on
    REPLPUSH apply), zero acked-write loss, replica_fallbacks > 0 over
    the replica-kill window, convergence to ground truth after quiesce,
    and tracking tables drained flat when readers disconnect.
  * ``tracking`` — the near-cache coherence profile (ISSUE 7): zipf
    readers with server-assisted near caches (CLIENT TRACKING) keep
    reading while key-bearing slots migrate m0 -> m1 -> m0 and the
    write-owning master is killed and failed over.  Asserts ZERO stale
    tracked reads (no read ever goes backwards; every near cache
    converges to ground truth after quiesce) and that server tracking
    tables drain to zero when reader connections die.

Usage:
    JAX_PLATFORMS=cpu python tools/soak_smoke.py \
        [--profile standard|migration|cluster-proc|fleet|tracking]
        [--cycles N] [--seed S] [--phase SECONDS] [--no-kill]

Exit code 0 = every assertion held; the report summary prints either way.
"""
import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--profile",
                    choices=("standard", "migration", "cluster-proc",
                             "fleet", "fleet-host", "tracking",
                             "read-scale", "device-shard", "device-fault",
                             "qos", "vector", "residency"),
                    default="standard")
    ap.add_argument("--cycles", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--phase", type=float, default=1.0,
                    help="seconds of workload per phase (standard profile)")
    ap.add_argument("--no-kill", action="store_true",
                    help="standard profile: workload + reshard only")
    ap.add_argument("--shards", type=int, default=1,
                    help="vector profile: SHARDS for the soaked index — "
                         "> 1 runs the mesh-sharded leg (ISSUE 15: fan-out "
                         "legs + on-device merge under rebalance)")
    ap.add_argument("--replicas", type=int, default=0,
                    help="cluster-proc profile: replicas per master — > 0 "
                         "spawns replica PROCESSES and adds a "
                         "read_mode=replica reader to the workload, so "
                         "replica-served reads ride the multi-process "
                         "supervisor fleet (ISSUE 18 satellite)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    if args.profile == "vector":
        from redisson_tpu.chaos.soak import VectorSoakConfig, VectorSoakHarness

        harness = VectorSoakHarness(VectorSoakConfig(
            cycles=args.cycles, seed=args.seed, shards=args.shards,
        ))
    elif args.profile == "qos":
        from redisson_tpu.chaos.soak import QosSoakConfig, QosSoakHarness

        harness = QosSoakHarness(QosSoakConfig(
            cycles=args.cycles, seed=args.seed,
        ))
    elif args.profile == "device-shard":
        from redisson_tpu.chaos.soak import (
            DeviceShardSoakConfig, DeviceShardSoakHarness,
        )

        harness = DeviceShardSoakHarness(DeviceShardSoakConfig(
            cycles=args.cycles, seed=args.seed,
        ))
    elif args.profile == "device-fault":
        from redisson_tpu.chaos.soak import (
            DeviceFaultSoakConfig, DeviceFaultSoakHarness,
        )

        harness = DeviceFaultSoakHarness(DeviceFaultSoakConfig(
            cycles=args.cycles, seed=args.seed,
        ))
    elif args.profile == "residency":
        from redisson_tpu.chaos.soak import (
            ResidencySoakConfig, ResidencySoakHarness,
        )

        harness = ResidencySoakHarness(ResidencySoakConfig(
            cycles=args.cycles, seed=args.seed,
        ))
    elif args.profile == "read-scale":
        from redisson_tpu.chaos.soak import (
            ReadScaleSoakConfig, ReadScaleSoakHarness,
        )

        harness = ReadScaleSoakHarness(ReadScaleSoakConfig(
            cycles=args.cycles, seed=args.seed,
            kill=not args.no_kill,
        ))
    elif args.profile == "tracking":
        from redisson_tpu.chaos.soak import (
            TrackingSoakConfig, TrackingSoakHarness,
        )

        harness = TrackingSoakHarness(TrackingSoakConfig(
            cycles=args.cycles, seed=args.seed,
            kill=not args.no_kill,
        ))
    elif args.profile == "fleet-host":
        from redisson_tpu.chaos.soak import (
            HostFleetSoakConfig, HostFleetSoakHarness,
        )

        harness = HostFleetSoakHarness(HostFleetSoakConfig(
            cycles=args.cycles, seed=args.seed,
            # smoke = one whole-host kill + partition mid-drain; the
            # 2-cycle host-kill matrix runs in tests/test_soak.py's slow
            # tier
            crash_phases=("DRAINING:1",),
        ))
    elif args.profile == "fleet":
        from redisson_tpu.chaos.soak import FleetSoakConfig, FleetSoakHarness

        harness = FleetSoakHarness(FleetSoakConfig(
            cycles=args.cycles, seed=args.seed,
            # smoke = one target double-kill phase + roll of the masters +
            # promotion + live-coordinator kill; the kill-every-phase
            # matrix runs in tests/test_cluster_proc.py's slow tier
            crash_phases=("DRAINING:1",),
        ))
    elif args.profile == "cluster-proc":
        from redisson_tpu.chaos.soak import (
            ClusterProcSoakConfig, ClusterProcSoakHarness,
        )

        harness = ClusterProcSoakHarness(ClusterProcSoakConfig(
            cycles=args.cycles, seed=args.seed,
            # smoke = the sharpest single phase (SIGKILL mid-drain); the
            # full phase matrix runs in tests/test_cluster_proc.py's slow
            # tier — one phase keeps the smoke inside its 60s budget
            crash_phases=("DRAINING:1",),
            replicas=args.replicas,
        ))
    elif args.profile == "migration":
        from redisson_tpu.chaos.soak import (
            MigrationSoakConfig, MigrationSoakHarness,
        )

        harness = MigrationSoakHarness(MigrationSoakConfig(
            cycles=args.cycles, seed=args.seed,
        ))
    else:
        from redisson_tpu.chaos.soak import SoakConfig, SoakHarness

        harness = SoakHarness(SoakConfig(
            cycles=args.cycles,
            seconds_per_phase=args.phase,
            seed=args.seed,
            kill=not args.no_kill,
        ))
    try:
        report = harness.run()
    except AssertionError as e:
        print(f"SOAK FAILED: {e}")
        print(harness.report.summary())
        return 1
    print(report.summary())
    print("SOAK OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
