#!/usr/bin/env python
"""10-second soak for local sanity: one full chaos cycle (workload under
injected transport faults -> master kill -> automatic failover -> recovery
-> mesh reshard 4 -> 8 -> 4) with the same zero-acked-write-loss and
flat-census assertions the slow endurance tier enforces.

Usage:
    JAX_PLATFORMS=cpu python tools/soak_smoke.py [--cycles N] [--seed S]
                                                 [--phase SECONDS] [--no-kill]

Exit code 0 = every assertion held; the report summary prints either way.
"""
import argparse
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--cycles", type=int, default=1)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--phase", type=float, default=1.0,
                    help="seconds of workload per phase")
    ap.add_argument("--no-kill", action="store_true",
                    help="workload + reshard only (no master kill)")
    args = ap.parse_args()

    import jax

    jax.config.update("jax_platforms", "cpu")

    from redisson_tpu.chaos.soak import SoakConfig, SoakHarness

    cfg = SoakConfig(
        cycles=args.cycles,
        seconds_per_phase=args.phase,
        seed=args.seed,
        kill=not args.no_kill,
    )
    harness = SoakHarness(cfg)
    try:
        report = harness.run()
    except AssertionError as e:
        print(f"SOAK FAILED: {e}")
        print(harness.report.summary())
        return 1
    print(report.summary())
    print("SOAK OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
