"""Perf regression gate (ISSUE 2 satellite): compare a fresh bench.py run
against the latest recorded BENCH_rNN.json, per config.

The headline throughput slid three rounds in a row (8.17M -> 8.03M -> 7.71M
contains/s, BENCH_r03..r05) before anyone was forced to look; this gate makes
that slide impossible to miss again.  It is the pre-commit perf ritual
(README "Performance"): run bench.py on the chip, feed the JSON here, commit
only when the gate is green or the miss is explicitly traded out in ROADMAP.

Usage:
  python tools/perf_gate.py --fresh out.json      # out.json = bench.py stdout
  python bench.py | tee out.txt; python tools/perf_gate.py --fresh out.txt
  python tools/perf_gate.py --run                 # runs bench.py itself
  python tools/perf_gate.py --fresh out.json --baseline BENCH_r03.json

Inputs accept either the raw bench.py JSON line (possibly embedded in other
stdout) or a recorded BENCH_rNN.json wrapper ({"parsed": {...}}).  Baseline
defaults to the highest-numbered BENCH_r*.json in the repo root.

Gate rule: exit nonzero on a >5% drop (--threshold) in any GATED metric:
the HEADLINE (windowed bank contains/s), CONFIG5 (cluster mixed ops/s),
CONFIG2 flush p99 ms (lower is better — the latency floor the overlap
plane of ISSUE 3 attacks), and CONFIG4 cold entries/s.  Every other
tracked metric prints in the regression table and flags WARN on a drop —
visible, but advisory (tunnel variance on the secondary configs is real;
the gated numbers are windowed/best-of or percentile-stable).
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from typing import Dict, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (label, extractor-path, higher_is_better, gated)
# Gated set (exit nonzero on a >threshold regression): the windowed headline,
# config5, and — since the overlap plane (ISSUE 3) attacked the flush-latency
# floor — config2 flush p99 and the config4 COLD rate, so the latency the
# plane recovered cannot silently regress either.
METRICS = [
    ("headline bank contains/s", ("value",), True, True),
    ("config5 cluster mixed ops/s", ("details", "config5_cluster_mixed_ops_per_sec"), True, True),
    # config5p (ISSUE 6): the multi-process 8-master number — the only
    # cluster metric with no shared GIL.  Gated; on its FIRST appearance
    # (baseline has no config5p) the row reads n/a and passes — the fresh
    # run becomes the recorded baseline for the next round to defend.
    ("config5p cluster-proc mixed ops/s", ("details", "config5p_cluster_proc_ops_per_sec"), True, True),
    # config5d (ISSUE 8): ONE server owning the local device mesh — the
    # device-sharded throughput AND the 1-vs-N-device speedup ratio are both
    # gated (n/a-pass on first sight, >threshold relative drop after): a
    # regression in the ratio means the per-device lanes stopped
    # overlapping even if raw throughput moved for other reasons.
    ("config5d device-sharded ops/s", ("details", "config5d_device_sharded_ops_per_sec"), True, True),
    ("config5d speedup vs 1 device", ("details", "config5d_speedup_vs_1dev"), True, True),
    ("config1 single contains/s", ("details", "config1_single_filter_contains_per_sec"), True, False),
    ("config2 flush p99 ms", ("details", "config2_flush_p99_ms"), False, True),
    ("config3 hll add/s", ("details", "config3_hll_add_per_sec"), True, False),
    ("config3 hll merge pairs/s", ("details", "config3_hll_merge_pairs_per_sec"), True, False),
    ("config4 mapreduce entries/s", ("details", "config4_mapreduce_entries_per_sec"), True, False),
    ("config4 mapreduce COLD entries/s", ("details", "config4_mapreduce_cold_entries_per_sec"), True, True),
    # config6 (ISSUE 7): the tracking plane's server-op reduction at a 99%
    # read ratio.  Gated relative to baseline AND against an ABSOLUTE floor
    # (FLOORS below): reads must cost >=10x fewer server ops with tracking
    # on, every round, not merely "no worse than last round".
    ("config6 server-op reduction", ("details", "config6_server_op_reduction"), True, True),
    ("config6 tracked read ops/s", ("details", "config6_tracked_read_ops_per_sec"), True, False),
    # config6r (ISSUE 17): the read-scaling plane — 4-replica-vs-1-replica
    # read QPS ratio under the config5d CPU-replica occupancy convention
    # (auto-disarmed on a real TPU).  Gated relative (n/a-pass on first
    # sight) AND bound absolutely below: replicas must deliver >= 2.5x at
    # 4 replicas, and the p99 replica staleness under write traffic must
    # stay inside the CEILING — read scaling bought by serving stale data
    # is not read scaling.
    ("config6r read qps scaling", ("details", "config6r_read_qps_scaling"), True, True),
    ("config6r staleness p99 ms", ("details", "config6r_staleness_p99_ms"), False, False),
    # config2q (ISSUE 10): interactive tail latency under the hostile
    # mixed-tenant flood with the QoS scheduler armed, and the p99 fairness
    # ratio between equal-budget tenants.  Both gated relative to baseline
    # (n/a-pass on first sight) AND bound absolutely from first sight: the
    # fairness ratio by a 2x CEILING, the armed-vs-disarmed speedup by a
    # 1.2x floor (the scheduler must land interactive p99 materially below
    # the disarmed baseline on the same container, every round).
    ("config2q interactive p99 ms", ("details", "config2q_interactive_p99_ms"), False, True),
    ("config2q fairness p99 ratio", ("details", "config2q_fairness_p99_ratio"), False, True),
    ("config2q speedup vs no-qos", ("details", "config2q_interactive_speedup_vs_noqos"), True, False),
    # ISSUE 18: interactive p99 while a bulk tenant occupies the DEVICE
    # LANE, preemptible sub-windows + the per-class stream armed (gated
    # relative, lower-better); the armed-vs-no-preempt speedup and the
    # 2-node fleet fairness/admitted-ratio bind absolutely below.
    ("config2q preempt interactive p99", ("details", "config2q_preempt_interactive_p99_ms"), False, True),
    ("config2q cluster fairness ratio", ("details", "config2q_cluster_fairness_p99_ratio"), False, True),
    # config7 (ISSUE 11): device KNN throughput — gated relative
    # (n/a-pass on first sight, like every new config); the recall QUALITY
    # axis binds as an absolute floor below, not a relative row.
    ("config7 knn qps", ("details", "config7_knn_qps"), True, True),
    # config7 IVF leg (ISSUE 14): sub-linear cell-scored KNN at N=50k/d=128
    # — qps gated relative like the FLAT leg; its recall and its
    # speedup-vs-FLAT bind as absolute floors below, and the INT8 bank's
    # compression ratio as a ceiling (quality axes never gate relatively).
    ("config7 ivf knn qps", ("details", "config7_ivf_knn_qps"), True, True),
    # config7s (ISSUE 15): mesh-sharded KNN — row-parallel shard legs +
    # on-device top-k merge.  qps gated relative (n/a-pass first sight);
    # the recall floor and the 1-vs-n speedup floor bind absolutely below
    # (the speedup runs under the config5d CPU-replica occupancy model,
    # auto-disarmed on a real TPU).
    ("config7 sharded knn qps", ("details", "config7_sharded_knn_qps"), True, True),
    # config8 (ISSUE 20): tiered-HBM overcommit — zipf tenants at >=4x the
    # device budget served through demote-to-host + fault-in-on-first-touch.
    # Throughput gated relative (n/a-pass first sight); the hot-hit floor
    # and fault-in p99 ceiling bind absolutely below (the residency plane
    # may never buy throughput by thrashing or stalling).
    ("config8 overcommit ops/s", ("details", "config8_overcommit_ops_per_sec"), True, True),
    ("config8 hot hit ratio", ("details", "config8_hot_hit_ratio"), True, False),
    # observability (ISSUE 12): armed-vs-disarmed tracing throughput ratio
    # from tools/obs_overhead_bench.py — advisory relative row (n/a-pass
    # first sight); the binding bound is the ABSOLUTE floor below (armed
    # tracing may cost at most 3% on the config5-shaped mixed workload).
    ("obs armed tracing ratio", ("details", "obs_armed_overhead_ratio"), True, False),
]

# (label, extractor-path, minimum) — ABSOLUTE floors checked on the FRESH
# run alone: unlike the relative gate, a floor holds from the metric's first
# appearance (n/a only while the fresh run doesn't emit the metric at all).
FLOORS = [
    ("config6 server-op reduction >= 10x",
     ("details", "config6_server_op_reduction"), 10.0),
    ("config2q speedup vs no-qos >= 1.2x",
     ("details", "config2q_interactive_speedup_vs_noqos"), 1.2),
    # ISSUE 18: sub-windows + the per-class device stream must land the
    # interactive p99 materially below the whole-window no-preempt baseline
    # on the same container (the A/B runs under the config5d CPU-replica
    # occupancy model, auto-disarmed on a real TPU)
    ("config2q preempt speedup vs no-preempt >= 1.2x",
     ("details", "config2q_preempt_speedup_vs_nopreempt"), 1.2),
    # config7 recall@10 vs the float64 brute-force oracle: FLAT scoring is
    # exact in f32, so only rounding ties may differ — binding from first
    # sight (a recall drop means the kernel, not the workload, changed)
    ("config7 recall@10 >= 0.99",
     ("details", "config7_recall_at_10"), 0.99),
    # ISSUE 14: the sub-linear/compressed legs are only admissible while
    # their recall holds — floors bind from first sight so the speedup can
    # never be bought by silently giving up result quality
    ("config7 ivf recall@10 >= 0.97",
     ("details", "config7_ivf_recall_at_10"), 0.97),
    ("config7 ivf speedup vs FLAT >= 2x",
     ("details", "config7_ivf_speedup_vs_flat"), 2.0),
    ("config7 int8 recall@10 >= 0.95",
     ("details", "config7_int8_recall_at_10"), 0.95),
    # ISSUE 15: FLAT sharding is exact — the merge may cost ties only, so
    # the recall floor binds at the FLAT level from first sight; and the
    # row-parallel fan-out must actually WIN under the occupancy model
    # (>= 1.5x vs the same corpus on 1 shard) or the plane is overhead
    ("config7 sharded recall@10 >= 0.99",
     ("details", "config7_sharded_recall_at_10"), 0.99),
    ("config7 sharded speedup vs 1 shard >= 1.5x",
     ("details", "config7_sharded_speedup_vs_1shard"), 1.5),
    # armed tracing overhead (ISSUE 12): obs_overhead_bench.py's
    # armed/disarmed ops ratio — binds from first sight, n/a while absent
    ("obs armed tracing ratio >= 0.97",
     ("details", "obs_armed_overhead_ratio"), 0.97),
    # ISSUE 17: 4 replicas must actually absorb reads — >= 2.5x the
    # 1-replica read QPS on the zipf blob-read mix, from first sight
    ("config6r read qps scaling >= 2.5x",
     ("details", "config6r_read_qps_scaling"), 2.5),
    # ISSUE 20: the LRU clock must keep the zipf head device-resident —
    # >=90% of probe calls under 4x overcommit served with no fault-in
    ("config8 hot hit ratio >= 0.9",
     ("details", "config8_hot_hit_ratio"), 0.9),
    ("config8 overcommit ratio >= 4x",
     ("details", "config8_overcommit_ratio"), 4.0),
]

# (label, extractor-path, maximum) — ABSOLUTE ceilings, same first-sight
# discipline as FLOORS but bounding from above (lower is better).
CEILINGS = [
    ("config2q fairness p99 ratio <= 2x",
     ("details", "config2q_fairness_p99_ratio"), 2.0),
    # ISSUE 18: the fleet rebalance loop's two defended numbers on the
    # 2-node hostile mix — a tenant spraying every node is held to ~1x its
    # GLOBAL budget (without the loop the ratio sits near the node count),
    # and re-splitting the sprayer's budget must not starve either node's
    # interactive tenant (worst/best cross-node interactive p99)
    ("config2q cluster admitted ratio <= 1.5x",
     ("details", "config2q_cluster_admitted_ratio"), 1.5),
    ("config2q cluster fairness p99 <= 2x",
     ("details", "config2q_cluster_fairness_p99_ratio"), 2.0),
    # ISSUE 14: an INT8 bank must actually be compressed — quantized
    # device bytes at most 0.35x what f32 storage of the same rows costs
    ("config7 int8 bytes ratio <= 0.35x",
     ("details", "config7_int8_bytes_ratio"), 0.35),
    # ISSUE 17: p99 replica staleness (REPLSTATE receipt clock) through
    # the 4-replica read window with the writer active — replicas serving
    # reads must stay within the bounded-staleness contract's ballpark
    # (client-side bound in the bench is 2000ms; the sweep cadence plus
    # heartbeat keeps a healthy replica an order of magnitude fresher)
    ("config6r staleness p99 ms <= 1500",
     ("details", "config6r_staleness_p99_ms"), 1500.0),
    # ISSUE 20: a fault-in is one packed H2D plus (COLD) one verified spill
    # read — p99 must stay a bounded hiccup; anything near this ceiling
    # means promotion is rebuilding kernels or fighting the lane gate
    ("config8 fault-in p99 ms <= 250",
     ("details", "config8_fault_in_p99_ms"), 250.0),
]


def _extract(doc: dict, path: Tuple[str, ...]) -> Optional[float]:
    cur = doc
    for key in path:
        if not isinstance(cur, dict) or key not in cur:
            return None
        cur = cur[key]
    try:
        return float(cur)
    except (TypeError, ValueError):
        return None


def load_bench_doc(text: str) -> dict:
    """Parse a bench result from raw text: a BENCH_rNN wrapper, the bare
    bench.py JSON object, or stdout containing the JSON line."""
    try:
        doc = json.loads(text)
        if isinstance(doc, dict):
            if "parsed" in doc and isinstance(doc["parsed"], dict):
                return doc["parsed"]
            if "metric" in doc:
                return doc
    except json.JSONDecodeError:
        pass
    # scan line-wise for the bench JSON object (bench.py logs to stderr, but
    # callers often tee both streams into one file)
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            doc = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(doc, dict) and "metric" in doc:
            return doc
    raise SystemExit("no bench.py JSON result found in input")


def latest_baseline_path() -> str:
    paths = glob.glob(os.path.join(REPO, "BENCH_r*.json"))
    if not paths:
        raise SystemExit("no BENCH_r*.json baseline found in repo root")

    def round_no(p: str) -> int:
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        return int(m.group(1)) if m else -1

    return max(paths, key=round_no)


def compare(baseline: dict, fresh: dict, threshold: float) -> Tuple[list, bool]:
    """Per-metric rows + overall gate verdict."""
    rows = []
    ok = True
    for label, path, higher, gated in METRICS:
        b = _extract(baseline, path)
        f = _extract(fresh, path)
        if b is None or f is None or b == 0:
            rows.append((label, b, f, None, "n/a"))
            continue
        delta = (f - b) / b if higher else (b - f) / b
        regressed = delta < -threshold
        status = "OK"
        if regressed:
            status = "FAIL" if gated else "WARN"
            if gated:
                ok = False
        elif delta < 0:
            status = "fail(soft)" if gated else "warn(soft)"
        rows.append((label, b, f, delta, status))
    for label, path, floor in FLOORS:
        f = _extract(fresh, path)
        if f is None:
            rows.append((label, floor, f, None, "n/a"))
            continue
        passed = f >= floor
        rows.append((label, floor, f, None, "OK" if passed else "FAIL"))
        if not passed:
            ok = False
    for label, path, ceiling in CEILINGS:
        f = _extract(fresh, path)
        if f is None:
            rows.append((label, ceiling, f, None, "n/a"))
            continue
        passed = f <= ceiling
        rows.append((label, ceiling, f, None, "OK" if passed else "FAIL"))
        if not passed:
            ok = False
    return rows, ok


def render(rows, threshold: float) -> str:
    out = [
        f"{'metric':<34} {'baseline':>14} {'fresh':>14} {'delta':>8}  verdict",
        "-" * 82,
    ]
    for label, b, f, delta, status in rows:
        bs = f"{b:,.0f}" if isinstance(b, float) else "-"
        fs = f"{f:,.0f}" if isinstance(f, float) else "-"
        ds = f"{delta*+100:+.1f}%" if delta is not None else "-"
        out.append(f"{label:<34} {bs:>14} {fs:>14} {ds:>8}  {status}")
    out.append("-" * 82)
    out.append(
        f"gate: >{threshold:.0%} regression in headline, config5, config5p, "
        "config5d (ops/s AND 1-vs-N speedup), config2 flush p99, config4 "
        "cold, config6 reduction, config6r read scaling, config2q "
        "interactive p99, config2q fairness, config2q preempt p99, "
        "config2q cluster fairness, config7 knn qps, config7 ivf "
        "qps, config7 sharded qps, or config8 overcommit ops/s fails; "
        "other drops are advisory "
        "(WARN); a metric absent from the baseline reads n/a and passes "
        "(recorded on first sight).  Absolute floors (config6 reduction "
        ">= 10x, config6r read scaling >= 2.5x, config2q speedup vs "
        "no-qos >= 1.2x, config2q preempt speedup vs no-preempt >= 1.2x, "
        "config7 recall@10 >= 0.99, ivf recall >= 0.97 + "
        "ivf speedup >= 2x, int8 recall >= 0.95, sharded recall >= 0.99 + "
        "sharded speedup vs 1 shard >= 1.5x, armed tracing ratio >= 0.97, "
        "config8 hot-hit >= 0.9 + overcommit >= 4x) "
        "and ceilings (config2q fairness <= 2x, config2q cluster admitted "
        "ratio <= 1.5x + cluster fairness <= 2x, int8 bytes ratio <= "
        "0.35x, config6r staleness p99 <= 1500ms, config8 fault-in p99 <= "
        "250ms) bind from first sight."
    )
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="bench.py regression gate")
    ap.add_argument("--fresh", help="file holding a fresh bench.py result")
    ap.add_argument("--run", action="store_true", help="run bench.py now")
    ap.add_argument("--baseline", help="baseline file (default: latest BENCH_r*.json)")
    ap.add_argument("--threshold", type=float, default=0.05)
    args = ap.parse_args(argv)

    if bool(args.fresh) == bool(args.run):
        ap.error("exactly one of --fresh/--run is required")
    if args.run:
        import subprocess

        p = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py")],
            stdout=subprocess.PIPE, text=True,
        )
        if p.returncode != 0:
            raise SystemExit(f"bench.py failed rc={p.returncode}")
        fresh = load_bench_doc(p.stdout)
    else:
        with open(args.fresh) as fh:
            fresh = load_bench_doc(fh.read())

    bpath = args.baseline or latest_baseline_path()
    with open(bpath) as fh:
        baseline = load_bench_doc(fh.read())

    rows, ok = compare(baseline, fresh, args.threshold)
    print(f"baseline: {os.path.basename(bpath)}")
    print(render(rows, args.threshold))
    print("GATE:", "PASS" if ok else "FAIL")
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
