#!/usr/bin/env python
"""Standalone fleet-wide tenant QoS rebalancer (ISSUE 18).

Runs the cluster/qos_control.py control loop against ANY fleet addressed by
host:port — driver-spawned clusters whose supervisor lives in another
process (or no process at all), exactly like a sidecar: scrape every node's
``CLUSTER QOS`` tenant table, re-split each tenant's global rate across
nodes proportional to observed demand, push the split via ``CLUSTER QOS
REBALANCE``.

    python tools/qos_rebalance.py 127.0.0.1:7000 127.0.0.1:7001 \
        --rate 100000 --burst 150000 --interval 1.0

Runs until interrupted; ``--sweeps N`` exits after N sweeps (smoke/CI use).
"""
from __future__ import annotations

import argparse
import sys
import time
from contextlib import closing


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description="fleet-wide tenant QoS rebalancer")
    ap.add_argument("nodes", nargs="+", metavar="HOST:PORT",
                    help="master nodes to budget across")
    ap.add_argument("--rate", type=float, required=True,
                    help="each tenant's GLOBAL ops/s budget across the fleet")
    ap.add_argument("--burst", type=float, default=None,
                    help="global burst headroom (split with the rate)")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="seconds between control-loop sweeps")
    ap.add_argument("--min-share", type=float, default=0.05,
                    help="minimum fraction of an even split every node keeps")
    ap.add_argument("--password", default=None)
    ap.add_argument("--ca-cert", default=None, metavar="PEM",
                    help="fleet CA certificate: speak TLS to the nodes "
                         "(cross-host driver fleets arm TLS by default; "
                         "point this at the supervisor's tls/fleet.crt)")
    ap.add_argument("--weight", action="append", default=[],
                    metavar="TENANT=W",
                    help="per-tenant service-class weight (repeatable, e.g. "
                         "--weight gold=2.0 --weight silver=1.0); scales "
                         "that tenant's global budget and is pushed "
                         "fleet-wide via REBALANCE ... WEIGHT")
    ap.add_argument("--sweeps", type=int, default=0,
                    help="exit after this many sweeps (0 = run forever)")
    args = ap.parse_args(argv)

    from redisson_tpu.cluster.qos_control import QosRebalancer
    from redisson_tpu.net.client import Connection

    weights = {}
    for spec in args.weight:
        tenant, sep, w = spec.partition("=")
        if not sep or not tenant:
            ap.error(f"--weight expects TENANT=W, got {spec!r}")
        try:
            weights[tenant] = float(w)
        except ValueError:
            ap.error(f"--weight {spec!r}: weight is not a float")

    ssl_context = None
    if args.ca_cert:
        from redisson_tpu.net.client import client_ssl_context

        # fleet peers are addressed by IP/label: the chain pin (not the
        # hostname) is what keeps foreign certs out, same as the supervisor
        ssl_context = client_ssl_context(
            ca_file=args.ca_cert, verify_hostname=False,
        )

    def factory(addr: str):
        host, _, port = addr.rpartition(":")

        def open_conn():
            return closing(Connection(host, int(port), timeout=10.0,
                                      password=args.password,
                                      ssl_context=ssl_context))

        return open_conn

    rb = QosRebalancer(
        {a: factory(a) for a in args.nodes}, args.rate,
        global_burst=args.burst, interval=args.interval,
        min_share=args.min_share, tenant_weights=weights,
    )
    n = 0
    try:
        while True:
            pushed = rb.step()
            n += 1
            for tenant, split in sorted(pushed.items()):
                parts = ", ".join(
                    f"{node}={rate:.0f}" for node, rate in sorted(split.items())
                )
                print(f"[sweep {n}] {tenant}: {parts}", flush=True)
            if args.sweeps and n >= args.sweeps:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
