"""Per-stage waterfall dump of the server's trace ring (ISSUE 12).

Pulls ``TRACE GET`` over the wire and renders each frame trace as an ASCII
waterfall — one bar per stage span, offset/scaled against the frame's total
(client-observable) latency — so "WHERE did this frame's p99 go?" is
answerable from a terminal:

    $ python tools/trace_dump.py --port 6390 --n 5 --by total
    trace 184  BF.MEXISTS64 x1  total 63.1ms  class=interactive tenant=ta
      parse      0.0ms |#                                                 |
      qos        0.1ms |#                                                 |
      dispatch  12.4ms |....#########                                     |
      readback  48.9ms |.............###################################  |
      reply      1.2ms |..............................................### |

Arm tracing first (``CONFIG SET trace-enabled yes`` / ``RTPU_TRACE=1``);
``--by <stage>`` orders by one stage's summed duration (e.g. ``--by qos``
surfaces the frames that sat longest behind admission).  ``--json`` emits
the raw entries for dashboards.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

WIDTH = 50


def _b(x) -> str:
    return x.decode(errors="replace") if isinstance(x, (bytes, bytearray)) else str(x)


def render_trace(entry, width: int = WIDTH) -> str:
    """One wire trace entry -> waterfall text (entry shape: [id, unix_ms,
    total_us, verb, n_cmds, class, tenant, [[name, off, dur, attrs]...]])."""
    tid, _ts_ms, total_us, verb, n_cmds, cls, tenant, spans = entry
    total_us = max(int(total_us), 1)
    head = (
        f"trace {tid}  {_b(verb)} x{int(n_cmds)}  "
        f"total {total_us / 1000:.1f}ms"
    )
    if _b(cls):
        head += f"  class={_b(cls)}"
    if _b(tenant):
        head += f"  tenant={_b(tenant)}"
    lines = [head]
    for name, off_us, dur_us, attrs in spans:
        name = _b(name)
        if name.endswith(".member"):
            continue  # members duplicate their kernel span's interval
        lo = min(width, int(int(off_us) * width / total_us))
        ln = max(1, int(int(dur_us) * width / total_us))
        bar = "." * lo + "#" * min(ln, width - lo)
        bar += " " * (width - len(bar))
        extra = ""
        if attrs:
            kv = [
                f"{_b(attrs[i])}={_b(attrs[i + 1])}"
                for i in range(0, len(attrs), 2)
            ]
            extra = "  " + ",".join(kv)
        lines.append(
            f"  {name:<9}{int(dur_us) / 1000:>8.1f}ms |{bar}|{extra}"
        )
    return "\n".join(lines)


def fetch(host: str, port: int, n: int, by: str, password=None):
    from redisson_tpu.net.client import Connection

    conn = Connection(host, port, timeout=30.0, password=password)
    try:
        return conn.execute("TRACE", "GET", str(n), "BY", by, timeout=30.0)
    finally:
        conn.close()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6390)
    ap.add_argument("--password", default=None)
    ap.add_argument("--n", type=int, default=10, help="slowest-N traces")
    ap.add_argument(
        "--by", default="total",
        help="order by 'total' or one stage's summed duration "
             "(qos/stage/dispatch/kernel/readback/reply)",
    )
    ap.add_argument("--json", action="store_true", help="raw entries as JSON")
    args = ap.parse_args(argv)

    entries = fetch(args.host, args.port, args.n, args.by, args.password)
    if not entries:
        print(
            "trace ring is empty — arm tracing first: "
            "CONFIG SET trace-enabled yes (or RTPU_TRACE=1)",
            file=sys.stderr,
        )
        return 1
    if args.json:
        def clean(x):
            if isinstance(x, (bytes, bytearray)):
                return x.decode(errors="replace")
            if isinstance(x, list):
                return [clean(v) for v in x]
            return x

        print(json.dumps([clean(e) for e in entries], indent=1))
        return 0
    for e in entries:
        print(render_trace(e))
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
